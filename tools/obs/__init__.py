"""tools.obs — offline reporting over ``mmlspark_tpu.obs`` JSONL exports
and ``blackbox.rank<R>.jsonl`` flight-recorder dumps.

- ``python -m tools.obs report [--json] [path]`` aggregates the span
  records (and the final snapshot record each rank appends at exit) from
  a ``MMLSPARK_TPU_OBS=<path>`` run.  Multi-process runs write per-rank
  files (``<path>.rank<R>``); the report reads the base path plus every
  rank sibling it finds.
- ``python -m tools.obs report --diff A B`` diffs two runs' snapshots
  (counter deltas, histogram p50/p99 shifts) — each side may be a JSONL
  export, a raw snapshot JSON, or a ``tools/bench_*.py`` output JSON
  (whose embedded ``"obs"`` key is found automatically).
- ``python -m tools.obs timeline <paths...>`` merges per-rank blackbox
  dumps (and/or exports) onto one wall clock via each dump's paired
  wall/monotonic anchor, with per-step compute vs collective-wait
  attribution.
- ``python -m tools.obs trace <request_id>`` reconstructs one serving
  request's critical path (queue wait → batch-close wait → predict →
  reply) across the request/batch trace-id fan-in.
- ``python -m tools.obs drift [--json] [path | --url URL]`` summarizes
  the model-quality monitor's ``quality.*``/``slo.*`` series (drift
  alarms, PSI gauges, burn rates) from any snapshot-bearing file, or
  pulls a live app's ``GET /driftz`` for full per-feature detail.

Pure stdlib — usable on a machine without jax installed.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional


def discover_files(path: str) -> List[str]:
    """The base export file plus any ``<path>.rank<R>`` siblings."""
    files = []
    if os.path.isfile(path):
        files.append(path)
    files.extend(sorted(glob.glob(glob.escape(path) + ".rank*")))
    return files


def load_records(path: str) -> List[dict]:
    """All well-formed JSONL records across the export's rank files.
    Malformed lines (torn writes from a killed process) are skipped."""
    records: List[dict] = []
    for fn in discover_files(path):
        with open(fn, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records


def _rank_label(rec: dict, fallback: Optional[dict] = None):
    """Merge key for one record's writing process.  Plain runs keep the
    integer rank (exact pre-fleet behavior); fleet replicas — which are
    all rank 0 of their own process — append the ``replica`` tag their
    records carry, so N same-host replicas aggregate side by side
    instead of silently folding into one \"rank 0\".  Real multi-process
    records additionally carry jax's ``process_index``; when it disagrees
    with the launcher rank (coordinator renumbering, or records written
    before bring-up resolved the rank) the label keeps both so distinct
    processes never fold together."""
    fb = fallback or {}
    rank = rec.get("rank", fb.get("rank", 0))
    pi = rec.get("process_index", fb.get("process_index"))
    if pi is not None and pi != rank:
        rank = f"{rank}/p{pi}"
    rep = rec.get("replica") or fb.get("replica")
    return f"{rank}.{rep}" if rep else rank


def aggregate(records: List[dict]) -> dict:
    """Fold span records into per-name stats and step records
    (``obs/steps.py`` exports) into per-kind wall/compute/collective/
    ingest-stall attribution; keep the LAST snapshot per rank/replica
    (the exit-time one supersedes any mid-run export_snapshot)."""
    spans: Dict[str, dict] = {}
    steps: Dict[str, dict] = {}
    snapshots: Dict[str, dict] = {}
    ranks = set()
    for rec in records:
        kind = rec.get("kind")
        if kind == "span":
            name = rec.get("name", "?")
            dur = float(rec.get("dur_s", 0.0))
            rk = _rank_label(rec)
            ranks.add(rk)
            agg = spans.get(name)
            if agg is None:
                agg = spans[name] = {
                    "count": 0,
                    "total_s": 0.0,
                    "max_s": 0.0,
                    "ranks": set(),
                }
            agg["count"] += 1
            agg["total_s"] += dur
            agg["max_s"] = max(agg["max_s"], dur)
            agg["ranks"].add(rk)
        elif kind == "step":
            st = rec.get("step") or {}
            sk = str(st.get("kind", "?"))
            rk = _rank_label(rec)
            ranks.add(rk)
            agg = steps.get(sk)
            if agg is None:
                agg = steps[sk] = {
                    "count": 0,
                    "wall_s": 0.0,
                    "compute_s": 0.0,
                    "collective_s": 0.0,
                    "ingest_stall_s": 0.0,
                    "max_wall_s": 0.0,
                    "ranks": set(),
                }
            agg["count"] += 1
            for f in ("wall_s", "compute_s", "collective_s",
                      "ingest_stall_s"):
                try:
                    agg[f] += float(st.get(f, 0.0) or 0.0)
                except (TypeError, ValueError):
                    pass
            try:
                agg["max_wall_s"] = max(agg["max_wall_s"],
                                        float(st.get("wall_s", 0.0) or 0.0))
            except (TypeError, ValueError):
                pass
            agg["ranks"].add(rk)
        elif kind == "snapshot":
            rk = _rank_label(rec)
            ranks.add(rk)
            snapshots[str(rk)] = rec.get("snapshot", {})
    for agg in spans.values():
        agg["mean_s"] = agg["total_s"] / agg["count"]
        agg["ranks"] = sorted(agg.pop("ranks"), key=str)
    for agg in steps.values():
        agg["mean_wall_s"] = agg["wall_s"] / agg["count"]
        agg["ranks"] = sorted(agg.pop("ranks"), key=str)
    return {
        "span_records": sum(a["count"] for a in spans.values()),
        "step_records": sum(a["count"] for a in steps.values()),
        "ranks": sorted(ranks, key=str),
        "spans": spans,
        "steps": steps,
        "device": _device_sections(snapshots),
        "snapshots": snapshots,
    }


def _device_sections(snapshots: Dict[str, dict]) -> dict:
    """Per-rank device-memory gauges + compile-event counters
    (``obs/device.py`` series) pulled out of the exit snapshots."""
    out: Dict[str, dict] = {}
    for rank, snap in snapshots.items():
        mem = {
            k: float(v) for k, v in (snap.get("gauges") or {}).items()
            if k.startswith("device.")
        }
        comp = {
            k: float(v) for k, v in (snap.get("counters") or {}).items()
            if k.startswith("device.compile_events")
        }
        if mem or comp:
            out[rank] = {"memory": mem, "compile_events": comp}
    return out


def render_text(report: dict, files: List[str]) -> str:
    out: List[str] = []
    out.append(
        f"obs report — {len(files)} file(s), "
        f"{report['span_records']} span record(s), "
        f"{report.get('step_records', 0)} step record(s), "
        f"rank(s) {report['ranks'] or [0]}"
    )
    if report["spans"]:
        out.append("")
        out.append(
            f"  {'span':<40} {'count':>7} {'total_s':>10} "
            f"{'mean_s':>10} {'max_s':>10}"
        )
        for name in sorted(
            report["spans"], key=lambda n: -report["spans"][n]["total_s"]
        ):
            a = report["spans"][name]
            out.append(
                f"  {name:<40} {a['count']:>7} {a['total_s']:>10.4f} "
                f"{a['mean_s']:>10.4f} {a['max_s']:>10.4f}"
            )
    if report.get("steps"):
        out.append("")
        out.append(
            f"  {'step kind':<12} {'count':>7} {'wall_s':>10} "
            f"{'compute_s':>10} {'collect_s':>10} {'stall_s':>10} "
            f"{'mean_s':>9}"
        )
        for sk in sorted(
            report["steps"], key=lambda k: -report["steps"][k]["wall_s"]
        ):
            a = report["steps"][sk]
            out.append(
                f"  {sk:<12} {a['count']:>7} {a['wall_s']:>10.4f} "
                f"{a['compute_s']:>10.4f} {a['collective_s']:>10.4f} "
                f"{a['ingest_stall_s']:>10.4f} {a['mean_wall_s']:>9.4f}"
            )
    for rank in sorted(report.get("device") or {}):
        d = report["device"][rank]
        out.append("")
        out.append(f"  device (rank {rank}):")
        for k in sorted(d["memory"]):
            out.append(f"    gauge    {k} = {d['memory'][k]:g}")
        for k in sorted(d["compile_events"]):
            out.append(f"    counter  {k} = {d['compile_events'][k]:g}")
    for rank in sorted(report["snapshots"]):
        snap = report["snapshots"][rank]
        counters = snap.get("counters", {})
        gauges = snap.get("gauges", {})
        hists = snap.get("histograms", {})
        out.append("")
        out.append(f"  snapshot (rank {rank}):")
        for k in sorted(counters):
            out.append(f"    counter  {k} = {counters[k]:g}")
        for k in sorted(gauges):
            out.append(f"    gauge    {k} = {gauges[k]:g}")
        for k in sorted(hists):
            h = hists[k]
            if h.get("count"):
                out.append(
                    f"    hist     {k}: count={h['count']} "
                    f"mean={h['mean']:.6g} p50={h['p50']:.6g} "
                    f"p95={h['p95']:.6g} max={h['max']:.6g}"
                )
            else:
                out.append(f"    hist     {k}: count=0")
    if not report["spans"] and not report["snapshots"]:
        out.append("  (no records)")
    return "\n".join(out)


def build_report(path: str) -> dict:
    files = discover_files(path)
    report = aggregate(load_records(path))
    report["files"] = files
    return report


def default_path() -> Optional[str]:
    raw = os.environ.get("MMLSPARK_TPU_OBS", "").strip()
    if raw and raw.lower() not in ("0", "1", "false", "true", "off", "on"):
        return raw
    return None


# ---------------------------------------------------------------------------
# Flight-recorder (blackbox) reading.
#
# A blackbox file is a sequence of dump SEGMENTS: one ``flight_header``
# line (with a paired ``ts``/``mono_ns`` wall/monotonic anchor) followed
# by its ``flight`` event lines carrying raw ``t_ns`` monotonic stamps.
# Each event's wall time is ``header.ts - (header.mono_ns - t_ns)/1e9`` —
# per-rank monotonic clocks never cross files; only reconstructed wall
# times are merged.
# ---------------------------------------------------------------------------


def discover_blackbox(path: str) -> List[str]:
    """Blackbox files named by ``path``: a directory (its
    ``blackbox.rank*.jsonl`` children), a blackbox file itself, or an obs
    export base path (blackbox siblings in its directory)."""
    if os.path.isdir(path):
        return sorted(glob.glob(os.path.join(glob.escape(path),
                                             "blackbox.rank*.jsonl")))
    base = os.path.basename(path)
    if base.startswith("blackbox.") and os.path.isfile(path):
        return [path]
    d = os.path.dirname(os.path.abspath(path))
    return sorted(glob.glob(os.path.join(glob.escape(d),
                                         "blackbox.rank*.jsonl")))


def load_blackbox(path: str) -> List[dict]:
    """Events from one blackbox file, each with a reconstructed ``wall``
    timestamp and its segment's dump ``reason`` attached."""
    events: List[dict] = []
    header: Optional[dict] = None
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            kind = rec.get("kind")
            if kind == "flight_header":
                header = rec
            elif kind == "flight" and header is not None:
                try:
                    wall = float(header["ts"]) - (
                        int(header["mono_ns"]) - int(rec["t_ns"])
                    ) / 1e9
                except (KeyError, TypeError, ValueError):
                    continue
                events.append({
                    "rank": _rank_label(rec, header),
                    "wall": wall,
                    "ev": rec.get("ev", "?"),
                    "name": rec.get("name", "?"),
                    "thread": rec.get("thread", "?"),
                    "detail": rec.get("detail"),
                    "reason": header.get("reason", "?"),
                    "src": "flight",
                })
    return events


def _blackbox_anchors(path: str) -> List[dict]:
    """All ``flight_header`` records in a blackbox file."""
    out = []
    with open(path, "r") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == "flight_header":
                out.append(rec)
    return out


def _export_events(path: str) -> List[dict]:
    """Obs-export span records as timeline events (wall START time =
    record ``ts`` minus the measured duration; exports stamp wall time at
    span close)."""
    events = []
    for rec in load_records(path):
        if rec.get("kind") != "span":
            continue
        try:
            ts = float(rec["ts"])
            dur = float(rec.get("dur_s", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        events.append({
            "rank": _rank_label(rec),
            "wall": ts - dur,
            "ev": "span",
            "name": rec.get("name", "?"),
            "thread": "?",
            "detail": {"dur_s": dur, **(rec.get("attrs") or {})},
            "reason": "export",
            "src": "export",
        })
    return events


def _gather_timeline_events(paths: List[str]):
    """(files, events) across blackbox dumps and obs exports."""
    files: List[str] = []
    events: List[dict] = []
    for p in paths:
        bb = discover_blackbox(p)
        for fn in bb:
            if fn not in files:
                files.append(fn)
                events.extend(load_blackbox(fn))
        if not os.path.isdir(p) and not os.path.basename(p).startswith(
            "blackbox."
        ):
            for fn in discover_files(p):
                if fn not in files:
                    files.append(fn)
            events.extend(_export_events(p))
    events.sort(key=lambda e: e["wall"])
    return files, events


def _pair_flight_spans(events: List[dict]) -> List[dict]:
    """Match ``sb``/``se`` ring events into completed spans (per
    rank+thread, stack-wise, by name) and pass through pre-measured
    ``span`` events; returns span dicts with start/dur/attrs."""
    spans: List[dict] = []
    stacks: Dict[tuple, list] = {}
    for e in events:
        if e["ev"] == "span":
            d = dict(e["detail"] or {})
            dur = float(d.pop("dur_s", 0.0) or 0.0)
            spans.append({"rank": e["rank"], "name": e["name"],
                          "start": e["wall"] - dur, "dur_s": dur,
                          "attrs": d})
        elif e["ev"] == "sb":
            stacks.setdefault((e["rank"], e["thread"]), []).append(e)
        elif e["ev"] == "se":
            stack = stacks.get((e["rank"], e["thread"]), [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i]["name"] == e["name"]:
                    sb = stack.pop(i)
                    spans.append({
                        "rank": e["rank"], "name": e["name"],
                        "start": sb["wall"],
                        "dur_s": max(0.0, e["wall"] - sb["wall"]),
                        "attrs": dict(sb["detail"] or {}),
                    })
                    break
        elif e["ev"] == "collective_end":
            d = dict(e["detail"] or {})
            dur = float(d.pop("dur_s", 0.0) or 0.0)
            spans.append({"rank": e["rank"],
                          "name": f"collective.{e['name']}",
                          "start": e["wall"] - dur, "dur_s": dur,
                          "attrs": d})
    return spans


def build_timeline(paths: List[str], step_span: str = "booster.iteration"
                   ) -> dict:
    """Merge per-rank blackbox/export files onto one wall clock.

    Returns anchors (per-rank wall-minus-monotonic offsets — the
    alignment), the merged event list, per-step compute vs
    collective-wait attribution (collective time = watchdog-wrapped
    collective spans ENDING inside a ``step_span`` interval on the same
    rank), and per-rank collective totals."""
    files, events = _gather_timeline_events(paths)
    spans = _pair_flight_spans(events)

    anchors: Dict[str, dict] = {}
    for fn in files:
        if not os.path.basename(fn).startswith("blackbox."):
            continue
        for h in _blackbox_anchors(fn):
            rank = str(_rank_label(h))
            a = anchors.setdefault(
                rank, {"offset_s": None, "reasons": [], "segments": 0}
            )
            a["segments"] += 1
            a["reasons"].append(h.get("reason", "?"))
            try:
                # Wall-clock instant of this rank's monotonic epoch: the
                # cross-rank alignment constant.
                a["offset_s"] = float(h["ts"]) - int(h["mono_ns"]) / 1e9
            except (KeyError, TypeError, ValueError):
                pass

    collectives = [s for s in spans if s["name"].startswith("collective.")]
    col_totals: Dict[str, Dict[str, float]] = {}
    for c in collectives:
        per = col_totals.setdefault(str(c["rank"]), {})
        per[c["name"]] = per.get(c["name"], 0.0) + c["dur_s"]

    steps = []
    for s in spans:
        if s["name"] != step_span:
            continue
        end = s["start"] + s["dur_s"]
        col_s = sum(
            c["dur_s"] for c in collectives
            if c["rank"] == s["rank"]
            and s["start"] <= c["start"] + c["dur_s"] <= end
        )
        steps.append({
            "rank": s["rank"],
            "start": s["start"],
            "dur_s": s["dur_s"],
            "collective_s": col_s,
            "compute_s": max(0.0, s["dur_s"] - col_s),
            "attrs": s["attrs"],
        })
    steps.sort(key=lambda s: s["start"])

    return {
        "files": files,
        "ranks": sorted({e["rank"] for e in events}, key=str),
        "anchors": anchors,
        "events": events,
        "spans": spans,
        "steps": steps,
        "collective_totals": col_totals,
    }


def render_timeline(tl: dict, max_events: int = 200) -> str:
    out: List[str] = []
    out.append(
        f"obs timeline — {len(tl['files'])} file(s), "
        f"{len(tl['events'])} event(s), rank(s) {tl['ranks'] or [0]}"
    )
    for rank in sorted(tl["anchors"]):
        a = tl["anchors"][rank]
        off = a["offset_s"]
        out.append(
            f"  rank {rank}: {a['segments']} dump segment(s) "
            f"({', '.join(a['reasons'])}); monotonic epoch at wall "
            f"{off:.6f}" if off is not None else
            f"  rank {rank}: {a['segments']} dump segment(s)"
        )
    if tl["steps"]:
        out.append("")
        out.append(
            f"  {'step':<28} {'rank':>4} {'dur_s':>10} "
            f"{'compute_s':>10} {'collective_s':>13}"
        )
        for i, s in enumerate(tl["steps"]):
            label = str((s["attrs"] or {}).get("it", i))
            out.append(
                f"  {'iteration ' + label:<28} {s['rank']:>4} "
                f"{s['dur_s']:>10.4f} {s['compute_s']:>10.4f} "
                f"{s['collective_s']:>13.4f}"
            )
    if tl["collective_totals"]:
        out.append("")
        out.append("  collective wait totals:")
        for rank in sorted(tl["collective_totals"]):
            for name, tot in sorted(tl["collective_totals"][rank].items()):
                out.append(f"    rank {rank} {name:<32} {tot:>10.4f}s")
    events = tl["events"]
    if events:
        t0 = events[0]["wall"]
        shown = events[-max_events:]
        out.append("")
        out.append(
            f"  merged events (last {len(shown)} of {len(events)}; "
            f"t=0 at first event):"
        )
        for e in shown:
            detail = ""
            if e["detail"]:
                detail = " " + json.dumps(e["detail"], sort_keys=True,
                                          default=str)
            out.append(
                f"    +{e['wall'] - t0:10.6f}s rank{e['rank']} "
                f"[{e['thread']}] {e['ev']:<14} {e['name']}{detail}"
            )
    if not events:
        out.append("  (no events)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Per-request trace reconstruction.
#
# serve/app.py mints one trace id per request (honoring X-Request-Id) and
# records per-stage spans carrying ``rid``; the batch fan-in span
# (``serve.batch``) lists its ``members`` and binds its OWN batch trace id
# around predict, so the request → batch → predict chain is joined here.
# ---------------------------------------------------------------------------

_TRACE_STAGES = (
    "serve.queue_wait",
    "serve.batch_close_wait",
    "serve.reply",
    "serve.request",
)


def build_trace(request_id: str, paths: List[str]) -> dict:
    """Reconstruct one request's critical path from exports/blackboxes."""
    _, events = _gather_timeline_events(paths)
    spans = _pair_flight_spans(events)

    def attr(s, k):
        return (s.get("attrs") or {}).get(k)

    mine = [s for s in spans
            if attr(s, "rid") == request_id
            or attr(s, "trace_id") == request_id]
    stages: Dict[str, dict] = {}
    for s in mine:
        if s["name"] in _TRACE_STAGES and s["name"] not in stages:
            stages[s["name"]] = {"dur_s": s["dur_s"], "start": s["start"],
                                 "attrs": s["attrs"]}

    batch_id = None
    for s in mine:
        if attr(s, "batch"):
            batch_id = attr(s, "batch")
            break
    batch = None
    for s in spans:
        members = attr(s, "members") or []
        if s["name"] == "serve.batch" and (
            (batch_id and attr(s, "batch") == batch_id)
            or request_id in members
        ):
            batch_id = attr(s, "batch") or batch_id
            batch = {
                "batch_id": batch_id,
                "dur_s": s["dur_s"],
                "model": attr(s, "model"),
                "bucket": attr(s, "bucket"),
                "rows": attr(s, "rows"),
                "members": len(members),
            }
            break
    predict = [
        {"dur_s": s["dur_s"], "backend": attr(s, "backend"),
         "bucket": attr(s, "bucket"), "rows": attr(s, "rows")}
        for s in spans
        if s["name"] == "predict"
        and attr(s, "trace_id") in ((batch_id, request_id) if batch_id
                                    else (request_id,))
    ]
    admits = [
        e for e in events
        if e["ev"] == "admit" and (e["detail"] or {}).get("rid") == request_id
    ]
    return {
        "request_id": request_id,
        "found": bool(mine or admits),
        "stages": stages,
        "batch": batch,
        "predict": predict,
        "admits": [{"verdict": e["name"], "wall": e["wall"],
                    "route": (e["detail"] or {}).get("route")}
                   for e in admits],
    }


def render_trace(tr: dict) -> str:
    out = [f"obs trace — request {tr['request_id']}"]
    if not tr["found"]:
        out.append("  (no records found for this request id)")
        return "\n".join(out)
    for a in tr["admits"]:
        out.append(f"  admission: {a['verdict']} (route {a['route']})")
    order = list(_TRACE_STAGES)
    labels = {
        "serve.queue_wait": "queue wait",
        "serve.batch_close_wait": "batch-close wait",
        "serve.reply": "reply",
        "serve.request": "TOTAL (enqueue -> replied)",
    }
    for name in order[:2]:
        if name in tr["stages"]:
            out.append(
                f"  {labels[name]:<28} {tr['stages'][name]['dur_s']:.6f}s"
            )
    if tr["batch"]:
        b = tr["batch"]
        out.append(
            f"  {'batch predict':<28} {b['dur_s']:.6f}s  "
            f"(batch {b['batch_id']}, model {b['model']}, "
            f"bucket {b['bucket']}, {b['rows']} rows, "
            f"{b['members']} member request(s))"
        )
    for p in tr["predict"]:
        out.append(
            f"  {'  booster predict':<28} {p['dur_s']:.6f}s  "
            f"(backend {p['backend']}, bucket {p['bucket']})"
        )
    for name in order[2:]:
        if name in tr["stages"]:
            out.append(
                f"  {labels[name]:<28} {tr['stages'][name]['dur_s']:.6f}s"
            )
    st = tr["stages"].get("serve.request")
    if st and st.get("attrs", {}).get("bucket") is not None:
        out.append(f"  padding bucket: {st['attrs']['bucket']}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Snapshot diffing (report --diff A B).
# ---------------------------------------------------------------------------


def _merge_snapshots(snaps: List[dict]) -> dict:
    """Fold per-rank snapshots into one: counters/sums add, gauges take
    the last writer, histogram percentiles take the max across ranks (a
    conservative approximation — exact merge would need raw samples)."""
    out = {"counters": {}, "gauges": {}, "histograms": {}, "spans": {}}
    for snap in snaps:
        for k, v in (snap.get("counters") or {}).items():
            out["counters"][k] = out["counters"].get(k, 0.0) + float(v)
        for k, v in (snap.get("gauges") or {}).items():
            out["gauges"][k] = float(v)
        for k, h in (snap.get("histograms") or {}).items():
            if not h.get("count"):
                out["histograms"].setdefault(k, {"count": 0})
                continue
            m = out["histograms"].get(k)
            if not m or not m.get("count"):
                out["histograms"][k] = dict(h)
                continue
            m["count"] += h["count"]
            m["sum"] = m.get("sum", 0.0) + h.get("sum", 0.0)
            m["mean"] = m["sum"] / m["count"]
            m["min"] = min(m.get("min", h["min"]), h["min"])
            m["max"] = max(m.get("max", h["max"]), h["max"])
            for p in ("p50", "p95", "p99"):
                if p in h:
                    m[p] = max(m.get(p, h[p]), h[p])
        for k, s in (snap.get("spans") or {}).items():
            m = out["spans"].get(k)
            if m is None:
                out["spans"][k] = dict(s)
                continue
            m["count"] += s.get("count", 0)
            m["total_s"] += s.get("total_s", 0.0)
            m["max_s"] = max(m.get("max_s", 0.0), s.get("max_s", 0.0))
            m["mean_s"] = m["total_s"] / m["count"] if m["count"] else 0.0
    return out


def snapshot_from(path: str) -> dict:
    """A merged obs snapshot from ``path``: a JSONL export (per-rank
    snapshots merged), a raw ``obs.snapshot()`` JSON, or a bench output
    JSON carrying the snapshot under its ``"obs"`` key."""
    try:
        with open(path, "r") as f:
            d = json.load(f)
    except ValueError:
        d = None  # more than one JSON document: a JSONL export
    if isinstance(d, dict):
        if "counters" in d or "histograms" in d:
            return d
        if isinstance(d.get("obs"), dict):
            return d["obs"]
        if isinstance(d.get("snapshot"), dict):
            return d["snapshot"]
        raise ValueError(f"{path}: no obs snapshot found in JSON")
    report = aggregate(load_records(path))
    snaps = [report["snapshots"][r] for r in sorted(report["snapshots"])]
    if not snaps:
        raise ValueError(f"{path}: no snapshot records in export")
    return _merge_snapshots(snaps)


def diff_snapshots(a: dict, b: dict) -> dict:
    """B minus A: counter deltas, histogram p50/p99 shifts, span-aggregate
    shifts.  Keys present on either side are included."""
    out = {"counters": {}, "histograms": {}, "spans": {}}
    ca, cb = a.get("counters") or {}, b.get("counters") or {}
    for k in sorted(set(ca) | set(cb)):
        va, vb = float(ca.get(k, 0.0)), float(cb.get(k, 0.0))
        out["counters"][k] = {"a": va, "b": vb, "delta": vb - va}
    ha, hb = a.get("histograms") or {}, b.get("histograms") or {}
    for k in sorted(set(ha) | set(hb)):
        xa, xb = ha.get(k) or {}, hb.get(k) or {}
        ent = {"count": {"a": xa.get("count", 0), "b": xb.get("count", 0)}}
        for p in ("p50", "p99"):
            pa, pb = xa.get(p), xb.get(p)
            ent[p] = {
                "a": pa, "b": pb,
                "delta": (pb - pa) if pa is not None and pb is not None
                else None,
            }
        out["histograms"][k] = ent
    sa, sb = a.get("spans") or {}, b.get("spans") or {}
    for k in sorted(set(sa) | set(sb)):
        xa, xb = sa.get(k) or {}, sb.get(k) or {}
        out["spans"][k] = {
            "count": {"a": xa.get("count", 0), "b": xb.get("count", 0)},
            "total_s": {
                "a": xa.get("total_s", 0.0), "b": xb.get("total_s", 0.0),
                "delta": xb.get("total_s", 0.0) - xa.get("total_s", 0.0),
            },
        }
    return out


# ---------------------------------------------------------------------------
# Model-quality drift reporting (drift [--json] [path | --url URL]).
#
# Two sources, one summary: a metrics snapshot's ``quality.*``/``slo.*``
# series (offline — exports, snapshot JSONs, bench outputs), or a live
# app's ``GET /driftz`` payload (full per-feature detail).
# ---------------------------------------------------------------------------


def _split_series(key: str):
    """``name{k=v,...}`` -> (name, labels dict); plain names pass
    through with no labels."""
    if key.endswith("}") and "{" in key:
        name, _, inner = key.partition("{")
        labels = {}
        for part in inner[:-1].split(","):
            k, eq, v = part.partition("=")
            if eq:
                labels[k] = v
        return name, labels
    return key, {}


def build_drift(snap: dict) -> dict:
    """Per-model drift/SLO summary from a snapshot's quality.* and slo.*
    series (see :func:`snapshot_from` for accepted inputs)."""
    models: Dict[str, dict] = {}

    def m(name: str) -> dict:
        return models.setdefault(name, {
            "alarms": {}, "clears": {}, "psi": {}, "burn": {},
            "batches_dropped": 0.0,
        })

    for key, v in (snap.get("counters") or {}).items():
        name, labels = _split_series(key)
        model = labels.get("model", "?")
        if name == "quality.drift_alarms":
            m(model)["alarms"][labels.get("kind", "?")] = float(v)
        elif name == "quality.drift_clears":
            m(model)["clears"][labels.get("kind", "?")] = float(v)
        elif name == "quality.batches_dropped":
            m(model)["batches_dropped"] += float(v)
    for key, v in (snap.get("gauges") or {}).items():
        name, labels = _split_series(key)
        model = labels.get("model", "?")
        if name in ("quality.feature_psi_max", "quality.score_psi"):
            m(model)["psi"][name.split(".", 1)[1]] = float(v)
        elif name.startswith("slo.") and name.endswith("_burn"):
            kind = name[len("slo."):-len("_burn")]
            m(model)["burn"].setdefault(kind, {})[
                labels.get("window", "?")] = float(v)
    return {
        "models": models,
        "total_alarms": sum(
            sum(e["alarms"].values()) for e in models.values()
        ),
    }


def render_drift(d: dict) -> str:
    out = [
        f"obs drift — {len(d['models'])} model route(s), "
        f"{d['total_alarms']:g} alarm transition(s)"
    ]
    if not d["models"]:
        out.append(
            "  (no quality.* series in this snapshot — monitor disabled "
            "or no traffic served)"
        )
    for name in sorted(d["models"]):
        e = d["models"][name]
        out.append("")
        out.append(f"  model {name}:")
        for k in sorted(e["psi"]):
            out.append(f"    {k:<24} {e['psi'][k]:.4f}")
        for kind in sorted(e["burn"]):
            w = e["burn"][kind]
            out.append(
                f"    {kind + '_burn':<24} fast={w.get('fast', 0.0):.3f} "
                f"slow={w.get('slow', 0.0):.3f}"
            )
        for k in sorted(e["alarms"]):
            fired, cleared = e["alarms"][k], e["clears"].get(k, 0.0)
            state = "CLEARED" if cleared >= fired else "ACTIVE"
            out.append(f"    alarm {k:<18} x{fired:g} ({state})")
        if e["batches_dropped"]:
            out.append(
                f"    {'batches_dropped':<24} {e['batches_dropped']:g}"
            )
    return "\n".join(out)


def fetch_driftz(url: str) -> dict:
    """GET a live app's /driftz (``url`` may be the app base or the full
    /driftz path)."""
    import urllib.request

    base = url.rstrip("/")
    if not base.endswith("/driftz"):
        base += "/driftz"
    with urllib.request.urlopen(base, timeout=10) as r:
        return json.loads(r.read().decode("utf-8"))


def render_driftz(payload: dict) -> str:
    status = payload.get("status")
    if "routes" not in payload:
        return f"obs drift — /driftz status: {status or '?'}"
    routes = payload.get("routes") or {}
    out = [
        f"obs drift — /driftz ({status or 'ok'}), {len(routes)} route(s), "
        f"{payload.get('dropped_batches', 0)} dropped batch(es)"
    ]
    for name in sorted(routes):
        r = routes[name]
        ref = r.get("reference")
        out.append("")
        out.append(
            f"  route {name} (version {r.get('version')}, reference: "
            + (f"{ref['n_rows']} rows, {ref['num_features']} features)"
               if ref else "none — SLO tracking only)")
        )
        active = r.get("alarms_active") or {}
        out.append(
            "    alarms active: "
            + (", ".join(sorted(active)) if active else "none")
        )
        counts = r.get("alarm_counts") or {}
        if counts:
            out.append(
                "    alarm transitions: "
                + ", ".join(f"{k} x{v}" for k, v in sorted(counts.items()))
            )
        if r.get("stale_batches"):
            out.append(f"    stale batches (swap in flight): "
                       f"{r['stale_batches']}")
        fd = r.get("feature_drift")
        if fd:
            out.append(
                f"    feature drift: live_rows={fd.get('live_rows', 0):.0f} "
                f"excess_psi_max={fd.get('excess_psi_max', 0.0):.4f}"
            )
            for t in (fd.get("top") or [])[:5]:
                out.append(
                    f"      feature {t['feature']:<5} "
                    f"excess_psi={t['excess_psi']:.4f} "
                    f"(raw {t['psi']:.4f}, bias {t['psi_bias']:.4f}) "
                    f"missing={t['missing_rate']:.3f}"
                )
        sd = r.get("score_drift")
        if sd:
            line = (
                f"    score drift:   live_rows={sd.get('live_rows', 0):.0f} "
                f"excess_psi={sd.get('excess_psi', 0.0):.4f}"
            )
            if "class_mix_psi" in sd:
                line += f" class_mix_psi={sd['class_mix_psi']:.4f}"
            out.append(line)
            rec = sd.get("recent")
            if rec:
                out.append(
                    f"      recent scores: p50={rec['p50']:.4g} "
                    f"p95={rec['p95']:.4g} (n={rec['count']})"
                )
        slo = r.get("slo") or {}
        for kind in ("availability", "latency"):
            k = slo.get(kind)
            if k:
                alert = (slo.get("alerts") or {}).get(kind)
                out.append(
                    f"    slo {kind:<12} burn fast={k['fast']:.3f} "
                    f"slow={k['slow']:.3f}"
                    + ("  ** ALERT **" if alert else "")
                )
    return "\n".join(out)


def render_diff(diff: dict, label_a: str = "A", label_b: str = "B") -> str:
    out = [f"obs diff — {label_a} -> {label_b}"]
    changed = {
        k: v for k, v in diff["counters"].items() if v["delta"] != 0
    }
    if changed:
        out.append("")
        out.append(f"  {'counter':<44} {'a':>12} {'b':>12} {'delta':>12}")
        for k, v in changed.items():
            out.append(
                f"  {k:<44} {v['a']:>12g} {v['b']:>12g} {v['delta']:>+12g}"
            )
    shifted = {
        k: v for k, v in diff["histograms"].items()
        if any(v[p]["delta"] for p in ("p50", "p99")
               if v[p]["delta"] is not None)
    }
    if shifted:
        out.append("")
        out.append(
            f"  {'histogram':<44} {'p50 a':>10} {'p50 b':>10} "
            f"{'p99 a':>10} {'p99 b':>10}"
        )

        def g(x):
            return f"{x:.4g}" if x is not None else "-"

        for k, v in shifted.items():
            out.append(
                f"  {k:<44} {g(v['p50']['a']):>10} {g(v['p50']['b']):>10} "
                f"{g(v['p99']['a']):>10} {g(v['p99']['b']):>10}"
            )
    spans = {
        k: v for k, v in diff["spans"].items() if v["total_s"]["delta"]
    }
    if spans:
        out.append("")
        out.append(
            f"  {'span':<44} {'total_s a':>12} {'total_s b':>12} "
            f"{'delta':>12}"
        )
        for k, v in spans.items():
            t = v["total_s"]
            out.append(
                f"  {k:<44} {t['a']:>12.4f} {t['b']:>12.4f} "
                f"{t['delta']:>+12.4f}"
            )
    if len(out) == 1:
        out.append("  (no differences)")
    return "\n".join(out)
