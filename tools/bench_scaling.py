"""Multi-chip scaling evidence: weak scaling + collective-bytes accounting.

VERDICT r3 #4: nothing measured how the data-parallel/voting collectives
scale.  This tool produces the table BASELINE.md commits:

1. **Weak scaling** over 1→8 virtual CPU devices (fixed rows/device):
   steady train wall for ``tree_learner=data`` vs ``voting`` vs data with
   the bf16 histogram wire (``hist_psum_dtype="bfloat16"``), plus AUC so
   wire-precision tradeoffs are quality-gated.  Virtual CPU devices share
   one core, so WALL numbers measure collective/overhead growth (the
   shape of the curve), not real ICI speedup — the BYTES are the part
   that predicts v5e-32 behavior.
2. **Measured collective bytes**: every ``lax.psum`` / ``psum_scatter`` /
   ``all_gather`` the training program actually traces is recorded as the
   bytes each device RECEIVES from that call site (result shape × dtype —
   a tracing shim, so the numbers come from the real program, not a hand
   formula).  Each in-loop site executes once per grower pass, so the
   traced bytes ARE the per-pass wire volume.  For the bench-shape
   depthwise config the dominant term is the histogram merge: 3·W·F·B
   floats/pass under ``hist_merge="allreduce"`` vs the 3·W·F/D·B slice +
   a (D, 5, L) candidate all-gather under ``"reduce_scatter"`` (ISSUE 4),
   vs the elected top-2k slices (3·W·2k·B) + votes for voting-parallel.
   The ``data`` mode runs the AUTO-resolved default (asserted to be
   reduce_scatter on a real mesh — the benchmarked configuration IS the
   default configuration); ``data_allreduce`` pins the old merge so the
   comms ledger records the measured ratio.  Every traced call is also
   split per link tier (``axis_bytes``: intra-host vs inter-host, via
   ``parallel.distributed.axis_scope`` — ISSUE 14): on the flat 1-D mesh
   every byte is "inter"; the ``data_hier`` mode (D>=4) re-runs training
   on a (2 hosts × D/2) ``mesh2d`` pod with the hierarchical merge, whose
   inter column carries only the (D,5,L) winner exchange + the elected
   column's refinement histogram.
3. **psum vs psum_scatter microbench** on a histogram-shaped array — the
   transport-level bound for the reduce-scatter merge.

Usage:  python tools/bench_scaling.py            # full table (spawns children)
        python tools/bench_scaling.py --out F    # also write rows to F
                                                 # (atomic: F.new + rename,
                                                 # temp removed on failure)
        python tools/bench_scaling.py --child D  # one device count (internal)
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ROWS_PER_DEV = 32_768
F = 64
B = 256
ITERS = 10
LEAVES = 63
TOP_K = 8


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


class CollectiveRecorder:
    """Tracing shim over lax.psum / lax.psum_scatter / lax.all_gather:
    records the bytes each device RECEIVES per traced call site (result
    shape × dtype — psum: the full reduced array; psum_scatter: the 1/D
    slice; all_gather: the D-fold result).  Numbers reflect the REAL
    program's collectives (anything the grower adds or removes shows up
    here unprompted)."""

    def __init__(self):
        self.calls = []

    def _record(self, kind, out, axis_name):
        import jax

        from mmlspark_tpu.parallel.distributed import axis_scope

        scope = axis_scope(axis_name)
        for leaf in jax.tree_util.tree_leaves(out):
            if not hasattr(leaf, "shape"):
                continue  # psum of a Python scalar constant-folds to an
                # int (the axis-size idiom) — no bytes move
            self.calls.append((kind, tuple(leaf.shape), str(leaf.dtype),
                               int(np.prod(leaf.shape)) * leaf.dtype.itemsize,
                               scope))

    def __enter__(self):
        from jax import lax

        self._lax = lax
        self._psum, self._ag = lax.psum, lax.all_gather
        self._pscat = lax.psum_scatter

        def psum(x, axis_name, **kw):
            out = self._psum(x, axis_name, **kw)
            self._record("psum", out, axis_name)
            return out

        def all_gather(x, axis_name, **kw):
            out = self._ag(x, axis_name, **kw)
            self._record("all_gather", out, axis_name)
            return out

        def psum_scatter(x, axis_name, **kw):
            out = self._pscat(x, axis_name, **kw)
            self._record("reduce_scatter", out, axis_name)
            return out

        self._lax.psum, self._lax.all_gather = psum, all_gather
        self._lax.psum_scatter = psum_scatter
        return self

    def __exit__(self, *exc):
        self._lax.psum, self._lax.all_gather = self._psum, self._ag
        self._lax.psum_scatter = self._pscat

    def summary(self):
        out = {}
        for kind, shape, dtype, nbytes, _scope in self.calls:
            key = f"{kind}{list(shape)}:{dtype}"
            ent = out.setdefault(key, {"bytes": nbytes, "traced_calls": 0})
            ent["traced_calls"] += 1
        return out

    def total_bytes(self):
        """Σ received-bytes over every traced call — the per-pass wire
        volume of the in-loop sites plus one-off setup collectives."""
        return int(sum(c[3] for c in self.calls))

    def axis_bytes(self):
        """Per-link-tier split of :meth:`total_bytes` (ISSUE 14): every
        call's axis argument classified by
        :func:`mmlspark_tpu.parallel.distributed.axis_scope` — "intra"
        bytes ride a host's fast links on the 2D ``mesh2d`` pod, "inter"
        bytes cross the slow data axis.  On a flat 1-D mesh every
        collective runs over the data axis, so everything is "inter"."""
        out = {"inter": 0, "intra": 0}
        for _, _, _, nbytes, scope in self.calls:
            out[scope] = out.get(scope, 0) + nbytes
        return out


def make_data(n, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    w = rng.normal(size=F) * (rng.random(F) < 0.4)
    logits = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (logits + rng.logistic(size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


def _auc(y, p):
    from mmlspark_tpu.engine.eval_metrics import auc

    return float(auc(y, p))


def run_child(n_dev: int):
    # Must run BEFORE jax initializes a backend: newer jax exposes the
    # device count as a config option; older builds only honor the XLA
    # host-platform flag (main() also sets it in the child env).
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_dev}".strip()
    )
    # The collective-bytes ledger reads the PYTHON trace — an AOT
    # trace-cache replay skips tracing and would record zero collectives,
    # so the bench always re-traces (the compile cache still applies).
    os.environ["MMLSPARK_TPU_NO_TRACE_CACHE"] = "1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_dev)
    except AttributeError:
        pass  # old jax: XLA_FLAGS above is the only knob
    assert jax.device_count() == n_dev, jax.device_count()

    from mmlspark_tpu import obs
    from mmlspark_tpu.engine.booster import Dataset, train
    from mmlspark_tpu.ops.binning import BinMapper
    from mmlspark_tpu.parallel.mesh import default_mesh

    obs.enable()  # per-phase breakdowns ride along in the JSON row
    n = ROWS_PER_DEV * n_dev  # weak scaling: fixed rows per device
    X, y = make_data(n)
    bm = BinMapper(max_bin=B - 1).fit(X)
    ds = Dataset(X, y)
    ds.binned(bm)
    mesh = default_mesh() if n_dev > 1 else None
    base = dict(
        objective="binary", num_iterations=ITERS, num_leaves=LEAVES,
        max_bin=B - 1, min_data_in_leaf=20, grow_policy="depthwise",
        top_k=TOP_K,
    )
    results = {
        "n_devices": n_dev, "rows": n,
        "mesh_shape": [n_dev] if n_dev > 1 else [],
        "modes": {},
    }
    # "data" is the AUTO default path (resolves to reduce_scatter on a
    # real mesh — asserted below the same way bench.py pins the other
    # auto knobs); "data_allreduce" pins the pre-ISSUE-4 merge so the
    # comms ledger records the measured bytes ratio on identical trees.
    modes = [("data", dict(tree_learner="data"), None),
             ("data_allreduce", dict(tree_learner="data",
                                     hist_merge="allreduce"), None),
             ("data_bf16wire", dict(tree_learner="data",
                                    hist_merge="allreduce",
                                    hist_psum_dtype="bfloat16"), None),
             # ISSUE 9: int16 gradient buckets + integer merge wire — the
             # recorder shows the hist merge riding int16 (half the f32
             # bytes) and the AUC column quality-gates the quantization
             ("data_quantize", dict(tree_learner="data",
                                    hist_quantize="int16"), None),
             ("voting", dict(tree_learner="voting"), None)]
    if n_dev >= 4 and n_dev % 2 == 0:
        # ISSUE 14: the same devices as a (2 hosts × n/2) mesh2d pod —
        # the intra/inter columns show the hierarchical merge keeping the
        # histogram bulk on the fast feature axis and shipping only the
        # winner exchange + elected-column refinement across hosts.
        from mmlspark_tpu.parallel.mesh import mesh2d

        modes.insert(1, ("data_hier",
                         dict(tree_learner="data",
                              hist_merge="hierarchical"),
                         mesh2d(2, n_dev // 2)))
    if n_dev == 1:
        modes = [("data", dict(tree_learner="serial"), None)]
    for name, extra, mesh_over in modes:
        params = dict(base, **extra)
        m_use = mesh_over if mesh_over is not None else mesh
        with CollectiveRecorder() as rec:
            booster = train(params, ds, bin_mapper=bm, mesh=m_use)  # trace
        if name == "data" and n_dev > 1:
            # The benchmarked default IS the default configuration: a bare
            # tree_learner="data" run must land on the reduce-scatter
            # merge at this mesh/feature shape without opt-in knobs.
            assert booster.config.hist_merge == "reduce_scatter", \
                booster.config.hist_merge
        t0 = time.perf_counter()
        booster = train(params, ds, bin_mapper=bm, mesh=m_use)
        wall = time.perf_counter() - t0
        results["modes"][name] = {
            "steady_wall_s": round(wall, 3),
            "auc": round(_auc(y, booster.predict(X)), 5),
            "hist_merge": booster.config.hist_merge,
            "comm_traced_bytes": rec.total_bytes(),
            "axis_bytes": rec.axis_bytes(),
            "collectives": rec.summary(),
        }

    # psum vs psum_scatter microbench on a histogram-shaped array
    if n_dev > 1:
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        W = (LEAVES + 1) // 2 + 2  # the level window the grower uses
        shape = (3, W, F, B)
        h = jnp.ones((n_dev,) + shape, jnp.float32)

        def timed(fn, *args):
            fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) \
                else fn(*args).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(5):
                r = fn(*args)
                jax.tree_util.tree_leaves(r)[0].block_until_ready()
            return (time.perf_counter() - t0) / 5

        from mmlspark_tpu.parallel.mesh import shard_map_compat

        psum_f = jax.jit(shard_map_compat(
            lambda x: jax.lax.psum(x[0], "data"), mesh=mesh,
            in_specs=P("data"), out_specs=P()))
        scat_f = jax.jit(shard_map_compat(
            lambda x: jax.lax.psum_scatter(
                x[0], "data", scatter_dimension=3, tiled=True),
            mesh=mesh, in_specs=P("data"), out_specs=P(None, None, None, "data")))
        results["microbench"] = {
            "shape": list(shape),
            "psum_s": round(timed(psum_f, h), 5),
            "psum_scatter_s": round(timed(scat_f, h), 5),
        }
    results["obs"] = obs.snapshot()
    print(json.dumps(results))


def _write_atomic(path, rows):
    """Write ``rows`` as JSON to ``path`` via a ``.new`` temp file.

    The temp file is removed on any failure so an aborted run never
    leaves a stray ``<path>.new`` in the tree (and a half-written file
    never shadows the committed artifact).
    """
    tmp = path + ".new"
    try:
        with open(tmp, "w") as fh:
            json.dump(rows, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def main(out_path=None):
    rows = []
    for d in (1, 2, 4, 8):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("JAX_NUM_CPU_DEVICES", None)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child", str(d)],
                env=env, capture_output=True, text=True, timeout=2700,
            )
        except subprocess.TimeoutExpired:
            _log(f"child D={d} timed out")
            continue
        if proc.returncode != 0:
            _log(f"child D={d} failed:\n{proc.stderr[-3000:]}")
            continue
        rows.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        _log(f"D={d} done")
    print(json.dumps(rows, indent=1))
    if out_path:
        _write_atomic(out_path, rows)
    # Human summary table
    _log("\nD  rows    mode            wall(s)  AUC     merge           "
         "comm/pass  inter/intra      dominant collective")
    for r in rows:
        for mode, m in r["modes"].items():
            # Dominant term = the largest single traced collective (the
            # histogram merge in every mode; keyed psum[...] under
            # allreduce, reduce_scatter[...] under the ISSUE-4 merge).
            hist_key = max(
                m["collectives"],
                key=lambda k: m["collectives"][k]["bytes"],
                default="-",
            )
            hb = m["collectives"].get(hist_key, {}).get("bytes", 0)
            ab = m.get("axis_bytes", {})
            _log(f"{r['n_devices']}  {r['rows']:>7} {mode:<15} "
                 f"{m['steady_wall_s']:>7} {m['auc']:.4f} "
                 f"{m['hist_merge']:<15} "
                 f"{m['comm_traced_bytes']/1e6:>7.2f}MB  "
                 f"{ab.get('inter', 0)/1e6:.2f}/{ab.get('intra', 0)/1e6:.2f}MB  "
                 f"{hb/1e6:.2f} MB ({hist_key})")
        if "microbench" in r:
            mb = r["microbench"]
            _log(f"   microbench {mb['shape']}: psum={mb['psum_s']}s "
                 f"psum_scatter={mb['psum_scatter_s']}s")


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        run_child(int(sys.argv[2]))
    elif len(sys.argv) >= 3 and sys.argv[1] == "--out":
        main(out_path=sys.argv[2])
    else:
        main()
