"""Multi-chip scaling evidence: weak scaling + collective-bytes accounting.

VERDICT r3 #4: nothing measured how the data-parallel/voting collectives
scale.  This tool produces the table BASELINE.md commits:

1. **Weak scaling** over 1→8 virtual CPU devices (fixed rows/device):
   steady train wall for ``tree_learner=data`` vs ``voting`` vs data with
   the bf16 histogram wire (``hist_psum_dtype="bfloat16"``), plus AUC so
   wire-precision tradeoffs are quality-gated.  Virtual CPU devices share
   one core, so WALL numbers measure collective/overhead growth (the
   shape of the curve), not real ICI speedup — the BYTES are the part
   that predicts v5e-32 behavior.
2. **Measured collective bytes**: every ``lax.psum``/``all_gather`` the
   training program actually traces is recorded (shape × dtype at the
   call site — a tracing shim, so the numbers come from the real program,
   not a hand formula), scaled by the statically known pass count.  For
   the bench-shape depthwise config the dominant term is the histogram
   allreduce: 3·W·F·B floats/pass for data-parallel vs the elected
   top-2k slices (3·W·2k·B) + votes for voting-parallel.
3. **psum vs psum_scatter microbench** on a histogram-shaped array — the
   upper bound for a future reduce_scatter split search (each shard
   electing candidates for its own bin slice).

Usage:  python tools/bench_scaling.py            # full table (spawns children)
        python tools/bench_scaling.py --child D  # one device count (internal)
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ROWS_PER_DEV = 32_768
F = 64
B = 256
ITERS = 10
LEAVES = 63
TOP_K = 8


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


class CollectiveRecorder:
    """Tracing shim over lax.psum / lax.all_gather: records operand bytes
    per traced call site.  Numbers reflect the REAL program's collectives
    (anything the grower adds or removes shows up here unprompted)."""

    def __init__(self):
        self.calls = []

    def __enter__(self):
        from jax import lax

        self._lax = lax
        self._psum, self._ag = lax.psum, lax.all_gather
        rec = self.calls

        def psum(x, axis_name, **kw):
            import jax

            for leaf in jax.tree_util.tree_leaves(x):
                rec.append(("psum", tuple(leaf.shape), str(leaf.dtype),
                            int(np.prod(leaf.shape)) * leaf.dtype.itemsize))
            return self._psum(x, axis_name, **kw)

        def all_gather(x, axis_name, **kw):
            import jax

            for leaf in jax.tree_util.tree_leaves(x):
                rec.append(("all_gather", tuple(leaf.shape), str(leaf.dtype),
                            int(np.prod(leaf.shape)) * leaf.dtype.itemsize))
            return self._ag(x, axis_name, **kw)

        self._lax.psum, self._lax.all_gather = psum, all_gather
        return self

    def __exit__(self, *exc):
        self._lax.psum, self._lax.all_gather = self._psum, self._ag

    def summary(self):
        out = {}
        for kind, shape, dtype, nbytes in self.calls:
            key = f"{kind}{list(shape)}:{dtype}"
            ent = out.setdefault(key, {"bytes": nbytes, "traced_calls": 0})
            ent["traced_calls"] += 1
        return out


def make_data(n, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    w = rng.normal(size=F) * (rng.random(F) < 0.4)
    logits = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (logits + rng.logistic(size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


def _auc(y, p):
    from mmlspark_tpu.engine.eval_metrics import auc

    return float(auc(y, p))


def run_child(n_dev: int):
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n_dev)

    from mmlspark_tpu import obs
    from mmlspark_tpu.engine.booster import Dataset, train
    from mmlspark_tpu.ops.binning import BinMapper
    from mmlspark_tpu.parallel.mesh import default_mesh

    obs.enable()  # per-phase breakdowns ride along in the JSON row
    n = ROWS_PER_DEV * n_dev  # weak scaling: fixed rows per device
    X, y = make_data(n)
    bm = BinMapper(max_bin=B - 1).fit(X)
    ds = Dataset(X, y)
    ds.binned(bm)
    mesh = default_mesh() if n_dev > 1 else None
    base = dict(
        objective="binary", num_iterations=ITERS, num_leaves=LEAVES,
        max_bin=B - 1, min_data_in_leaf=20, grow_policy="depthwise",
        top_k=TOP_K,
    )
    results = {"n_devices": n_dev, "rows": n, "modes": {}}
    modes = [("data", dict(tree_learner="data")),
             ("data_bf16wire", dict(tree_learner="data",
                                    hist_psum_dtype="bfloat16")),
             ("voting", dict(tree_learner="voting"))]
    if n_dev == 1:
        modes = [("data", dict(tree_learner="serial"))]
    for name, extra in modes:
        params = dict(base, **extra)
        with CollectiveRecorder() as rec:
            train(params, ds, bin_mapper=bm, mesh=mesh)  # compile + trace
        t0 = time.perf_counter()
        booster = train(params, ds, bin_mapper=bm, mesh=mesh)
        wall = time.perf_counter() - t0
        results["modes"][name] = {
            "steady_wall_s": round(wall, 3),
            "auc": round(_auc(y, booster.predict(X)), 5),
            "collectives": rec.summary(),
        }

    # psum vs psum_scatter microbench on a histogram-shaped array
    if n_dev > 1:
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        W = (LEAVES + 1) // 2 + 2  # the level window the grower uses
        shape = (3, W, F, B)
        h = jnp.ones((n_dev,) + shape, jnp.float32)

        def timed(fn, *args):
            fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) \
                else fn(*args).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(5):
                r = fn(*args)
                jax.tree_util.tree_leaves(r)[0].block_until_ready()
            return (time.perf_counter() - t0) / 5

        from mmlspark_tpu.parallel.mesh import shard_map_compat

        psum_f = jax.jit(shard_map_compat(
            lambda x: jax.lax.psum(x[0], "data"), mesh=mesh,
            in_specs=P("data"), out_specs=P()))
        scat_f = jax.jit(shard_map_compat(
            lambda x: jax.lax.psum_scatter(
                x[0], "data", scatter_dimension=3, tiled=True),
            mesh=mesh, in_specs=P("data"), out_specs=P(None, None, None, "data")))
        results["microbench"] = {
            "shape": list(shape),
            "psum_s": round(timed(psum_f, h), 5),
            "psum_scatter_s": round(timed(scat_f, h), 5),
        }
    results["obs"] = obs.snapshot()
    print(json.dumps(results))


def main():
    rows = []
    for d in (1, 2, 4, 8):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("JAX_NUM_CPU_DEVICES", None)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(d)],
            env=env, capture_output=True, text=True, timeout=1200,
        )
        if proc.returncode != 0:
            _log(f"child D={d} failed:\n{proc.stderr[-3000:]}")
            continue
        rows.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        _log(f"D={d} done")
    print(json.dumps(rows, indent=1))
    # Human summary table
    _log("\nD  rows    mode            wall(s)  AUC      hist-allreduce/pass")
    for r in rows:
        for mode, m in r["modes"].items():
            hist_key = next(
                (k for k in m["collectives"] if "psum[3," in k), "-"
            )
            hb = m["collectives"].get(hist_key, {}).get("bytes", 0)
            _log(f"{r['n_devices']}  {r['rows']:>7} {mode:<15} "
                 f"{m['steady_wall_s']:>7} {m['auc']:.4f}  "
                 f"{hb/1e6:.2f} MB ({hist_key})")
        if "microbench" in r:
            mb = r["microbench"]
            _log(f"   microbench {mb['shape']}: psum={mb['psum_s']}s "
                 f"psum_scatter={mb['psum_scatter_s']}s")


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        run_child(int(sys.argv[2]))
    else:
        main()
