"""Stacked many-model training bench: K boosters, ONE XLA dispatch.

The workload is the retrain queue's shape: K small tenants, every one a
different row count (real traffic windows never agree), all sharing one
binning authority.  Two ways to train the fleet:

- **sequential** — K standalone ``train()`` calls.  Every distinct row
  count is a distinct XLA program, so the baseline pays K traces + K
  compiles + K dispatches; that per-shape overhead IS what the bench
  measures, because it is what the one-at-a-time retrain drain pays in
  production.
- **stacked** — ONE ``engine.multi_train`` call: pad to a common
  ``(K, N, F)`` stack, trace once, compile once, dispatch once.

Parity is a hard gate in every mode: each stacked model must be
BITWISE-identical (predictions and leaf tables) to its sequential twin.
One-dispatch is asserted from the ``train.multi.dispatches`` counter,
one-program from the module's trace ledger.

The e2e leg replays the queue-to-fleet story hermetically: two
simultaneous drift alarms enter a :class:`RetrainController` queue,
``_drain_batch`` pops both severity-ordered, their warm-start refits
ride one stacked dispatch, and the fresh models hot-swap into a live
:class:`CoResidentGroup` via ``prepare_swap_many``/``commit_swap_many``
while pump threads hammer ``predict_mixed`` — zero errors allowed (the
in-process equivalent of the serving bench's zero-5xx gate).

The report is written as ``MULTI_TRAIN_BENCH.json`` (schema- and
gate-checked by ``tools.bench_ratchet``).  ``--smoke`` shrinks the run
(K=8 only) and exits non-zero unless every mechanism gate holds; the
speedup gate is advisory on cpu in smoke mode (CI boxes are noisy) and
ratcheted on the committed full-run ledger instead.

Usage::

    JAX_PLATFORMS=cpu python -m tools.bench_multi_train [--smoke]
        [--json PATH] [--iters N] [--seed K]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_FEATURES = 8


def _log(*a):
    print("[multi_train]", *a, flush=True)


def _counter(snapshot, prefix) -> float:
    return float(sum(
        v for k, v in snapshot.get("counters", {}).items()
        if k == prefix or k.startswith(prefix + "{")
    ))


def _tenant_rows(k: int, i: int) -> int:
    # 37 is coprime with 64, so up to K=64 every tenant gets a DISTINCT
    # row count — the fleet-of-shapes workload the stacking removes.
    # The spread is kept narrow (≤1.7x) so the bench isolates the
    # per-shape trace+compile+dispatch overhead rather than charging
    # the stacked path for padding every tenant to the widest window.
    return 768 + ((i * 37) % 64) * 8


def _make_dataset(rows: int, seed: int):
    from mmlspark_tpu.engine.booster import Dataset

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, N_FEATURES))
    y = X[:, 1] + 0.5 * X[:, 2] ** 2 + 0.1 * rng.normal(size=rows)
    return Dataset(X, y)


def _base_params(iters: int) -> dict:
    return {
        "objective": "regression",
        "num_leaves": 15,
        "num_iterations": iters,
        "learning_rate": 0.1,
        "min_data_in_leaf": 5,
    }


# --------------------------------------------------------------------------
# stacked vs sequential
# --------------------------------------------------------------------------
def run_stack_leg(k: int, iters: int, seed: int) -> dict:
    from mmlspark_tpu import obs
    from mmlspark_tpu.engine import multi_train as mt
    from mmlspark_tpu.engine.booster import TrainConfig, train

    params = _base_params(iters)
    datasets = [
        _make_dataset(_tenant_rows(k, i), seed * 1000 + i) for i in range(k)
    ]
    mapper = mt.fit_shared_mapper(datasets, params)
    jobs = []
    for i, ds in enumerate(datasets):
        p = dict(params, seed=seed + i, bagging_seed=31 + i)
        jobs.append(mt.MultiTrainJob(p, ds, name=f"tenant-{i}"))
        # binning is identical work on both paths — do it once, outside
        # both timers, so the clocks compare TRAINING alone
        ds.pin_mapper(mapper, TrainConfig.from_params(dict(p)))
        ds.binned(mapper)

    _log(f"K={k}: sequential baseline ({k} shapes, {k} programs)...")
    t0 = time.perf_counter()
    seq = [train(j.params, j.train_set) for j in jobs]
    sequential_s = time.perf_counter() - t0

    _log(f"K={k}: stacked (one program, one dispatch)...")
    snap0 = obs.snapshot()
    t0 = time.perf_counter()
    stacked = mt.multi_train(jobs, bin_mapper=mapper)
    stacked_s = time.perf_counter() - t0
    dispatches = int(
        _counter(obs.snapshot(), "train.multi.dispatches")
        - _counter(snap0, "train.multi.dispatches")
    )

    parity = True
    for job, a, b in zip(jobs, stacked, seq):
        X = np.asarray(job.train_set.X)
        pa, pb = np.asarray(a.predict(X)), np.asarray(b.predict(X))
        la = np.asarray(a.trees.leaf_value)
        lb = np.asarray(b.trees.leaf_value)
        if pa.tobytes() != pb.tobytes() or la.tobytes() != lb.tobytes():
            parity = False
            _log(f"  PARITY MISS {job.name}: "
                 f"maxdiff={np.abs(pa - pb).max()}")
    speedup = sequential_s / stacked_s if stacked_s > 0 else 0.0
    res = {
        "k": k,
        "iters": iters,
        "rows_total": int(sum(_tenant_rows(k, i) for i in range(k))),
        "sequential_s": round(sequential_s, 4),
        "stacked_s": round(stacked_s, 4),
        "speedup": round(speedup, 3),
        "parity_bitwise": parity,
        "dispatches": dispatches,
    }
    _log(f"K={k}: seq={sequential_s:.2f}s stacked={stacked_s:.2f}s "
         f"speedup={speedup:.2f}x parity={parity} "
         f"dispatches={dispatches}")
    return res


# --------------------------------------------------------------------------
# e2e: alarms -> batched drain -> one dispatch -> fleet hot swap
# --------------------------------------------------------------------------
class _GroupPump:
    """Background threads hammering ``predict_mixed`` across the swap —
    an exception here is the in-process 5xx."""

    def __init__(self, group, X, mids, clients=2):
        self.requests = 0
        self.errors = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._group, self._X, self._mids = group, X, mids
        self._threads = [
            threading.Thread(target=self._work, daemon=True)
            for _ in range(clients)
        ]
        for t in self._threads:
            t.start()

    def _work(self):
        while not self._stop.is_set():
            try:
                out = self._group.predict_mixed(self._X, self._mids)
                ok = bool(np.isfinite(out).all())
            except Exception:
                ok = False
            with self._lock:
                self.requests += 1
                if not ok:
                    self.errors += 1

    def stop(self) -> dict:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        return {"requests": self.requests, "errors": self.errors}


def run_e2e_leg(iters: int, seed: int) -> dict:
    from mmlspark_tpu import obs
    from mmlspark_tpu.engine import multi_train as mt
    from mmlspark_tpu.engine.booster import Dataset, TrainConfig, train
    from mmlspark_tpu.loop.controller import LoopConfig, RetrainController
    from mmlspark_tpu.serve.coresident import CoResidentGroup

    names = [f"tenant-{i}" for i in range(4)]
    params = _base_params(iters)
    datasets = {
        n: _make_dataset(384 + 64 * i, seed * 77 + i)
        for i, n in enumerate(names)
    }
    mapper = mt.fit_shared_mapper(list(datasets.values()), params)
    champions = {}
    for i, n in enumerate(names):
        p = dict(params, seed=seed + i)
        datasets[n].pin_mapper(mapper, TrainConfig.from_params(dict(p)))
        champions[n] = train(p, datasets[n])

    B = 64
    group = CoResidentGroup([(n, champions[n]) for n in names])
    group.prewarm([B])
    Xb = np.zeros((B, group.feature_dim), np.float32)
    Xb[:, :] = np.resize(
        np.asarray(datasets[names[0]].X, np.float32), Xb.shape
    )
    mids = np.arange(B, dtype=np.int32) % len(names)
    pump = _GroupPump(group, Xb, mids)

    # Two simultaneous drift alarms; the queue drains them as ONE batch,
    # severity first (the controller's admission path, no worker thread
    # — the bench drives the drain synchronously).
    controller = RetrainController(
        app=None, data_provider=lambda n: None,
        config=LoopConfig(train_batch=4, batch_window_s=0.0),
    )
    v1 = controller.request("tenant-1", reason="feature_drift",
                            severity=0.8)
    v2 = controller.request("tenant-3", reason="feature_drift",
                            severity=2.1)
    batch = controller._drain_batch()
    drained = [job.name for job, _ in batch]
    severity_ordered = drained == ["tenant-3", "tenant-1"]

    # Warm-start refit of the drained tenants on their fresh (shifted)
    # windows — ONE stacked dispatch for the whole batch.
    rng = np.random.default_rng(seed + 999)
    jobs = []
    for n in drained:
        Xf = rng.normal(size=(448, N_FEATURES)) + 1.5
        yf = Xf[:, 1] + 0.5 * Xf[:, 2] ** 2
        i = names.index(n)
        jobs.append(mt.MultiTrainJob(
            dict(params, seed=seed + i, num_iterations=max(4, iters // 2)),
            Dataset(Xf, yf), init_model=champions[n], name=n,
        ))
    snap0 = obs.snapshot()
    refit = mt.multi_train(jobs, bin_mapper=mapper)
    batched_dispatches = int(
        _counter(obs.snapshot(), "train.multi.dispatches")
        - _counter(snap0, "train.multi.dispatches")
    )

    # Hot-swap the whole batch into the serving group under traffic.
    updates = {n: b for n, b in zip(drained, refit)}
    group.prepare_swap_many(updates, buckets=[B])
    group.commit_swap_many(drained)
    time.sleep(0.5)  # post-swap traffic must drain clean
    traffic = pump.stop()

    # Post-swap parity: the group now serves the refit booster bitwise.
    n0 = drained[0]
    rows = np.asarray(datasets[n0].X)[:B]
    Xs = np.zeros((B, group.feature_dim), np.float32)
    Xs[: rows.shape[0], : rows.shape[1]] = rows
    ms = np.full(B, group.model_id(n0), np.int32)
    got = group.predict_mixed(Xs, ms)[: rows.shape[0], 0]
    padded = np.zeros((B, rows.shape[1]))
    padded[: rows.shape[0]] = rows
    want = np.asarray(
        updates[n0].predict_padded(padded, rows.shape[0]), np.float32
    )
    swap_parity = bool(np.array_equal(got, want))

    e2e = {
        "alarms": 2,
        "verdicts": [v1, v2],
        "batch": drained,
        "severity_ordered": bool(severity_ordered),
        "batched_dispatches": batched_dispatches,
        "swap_parity": swap_parity,
        **traffic,
    }
    _log(f"e2e: batch={drained} dispatches={batched_dispatches} "
         f"requests={traffic['requests']} errors={traffic['errors']} "
         f"swap_parity={swap_parity}")
    return e2e


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------
def run(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from mmlspark_tpu import obs

    obs.enable()
    backend = jax.default_backend()
    ks = [8] if args.smoke else [8, 64]
    report = {
        "bench": "multi_train",
        "backend": backend,
        "config": {
            "ks": ks,
            "iters": args.iters,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "n_features": N_FEATURES,
        },
        "results": [run_stack_leg(k, args.iters, args.seed) for k in ks],
    }
    report["e2e"] = run_e2e_leg(args.iters, args.seed)

    floor = 2.0 if backend == "cpu" else 5.0
    speedup_ok = all(r["speedup"] >= floor for r in report["results"])
    report["gates"] = {
        "parity_bitwise": all(
            r["parity_bitwise"] for r in report["results"]
        ),
        "one_dispatch_per_stack": all(
            r["dispatches"] == 1 for r in report["results"]
        ),
        "speedup_ok": bool(speedup_ok),
        "speedup_floor": floor,
        "e2e_zero_errors": (
            report["e2e"]["errors"] == 0 and report["e2e"]["requests"] > 0
        ),
        "e2e_one_dispatch": report["e2e"]["batched_dispatches"] == 1,
        "e2e_batched": len(report["e2e"]["batch"]) >= 2,
        "e2e_severity_ordered": report["e2e"]["severity_ordered"],
        "e2e_swap_parity": report["e2e"]["swap_parity"],
    }

    out = json.dumps(report, indent=2, default=str)
    if args.json_path:
        with open(args.json_path, "w") as f:
            f.write(out + "\n")
    print(out if not args.smoke else json.dumps(report["gates"], indent=1))

    if args.smoke:
        # Mechanism gates are hard anywhere; the wall-clock speedup gate
        # is advisory on cpu CI boxes and ratcheted on the committed
        # full-run ledger instead.
        hard = [g for g in report["gates"]
                if g not in ("speedup_ok", "speedup_floor")]
        if backend != "cpu":
            hard.append("speedup_ok")
        failures = [g for g in hard if not report["gates"][g]]
        if not speedup_ok and "speedup_ok" not in hard:
            _log(f"ADVISORY: speedup below {floor}x on {backend} "
                 "(not enforced in cpu smoke)")
        if failures:
            _log("MULTI-TRAIN SMOKE FAILED: " + ", ".join(failures))
            return 1
        _log("multi-train smoke OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.bench_multi_train")
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: K=8 only, hard-assert mechanism gates")
    ap.add_argument("--json", dest="json_path", default=None,
                    help="write the MULTI_TRAIN_BENCH report here")
    ap.add_argument("--iters", type=int, default=None,
                    help="trees per tenant (default 8 smoke, 16 full)")
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args(argv)
    if args.iters is None:
        args.iters = 8 if args.smoke else 16
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
